"""Llama-family decoder-only transformer (Flax linen), TPU-first.

The flagship model for the JAXJob examples and the benchmark harness
(BASELINE.md: Llama-2-7B FSDP on v5e-32). Design targets the MXU/HBM:

- bf16 params and activations; fp32 only where numerics demand it
  (RMSNorm accumulation, rotary tables, softmax, final logits).
- All FLOPs in large batched matmuls (einsum) that XLA tiles onto the MXU.
- `remat` on each block trades FLOPs for HBM (checkpointing).
- No data-dependent Python control flow — one static graph under jit.
- Attention defaults to `tf_operator_tpu.ops.attention`, which lowers to a
  Pallas flash-attention kernel on TPU and falls back to a fused XLA path
  elsewhere.

Reference note: the reference repo contains no model code (it is a control
plane; workloads live in user containers). Architecture follows the public
Llama-2 description (RMSNorm, RoPE, SwiGLU, optional GQA).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    remat: bool = True
    # Checkpoint policy under remat: "nothing" rematerializes everything
    # (min HBM, ~1/3 extra FLOPs in backward); "dots" saves matmul outputs
    # and recomputes only elementwise/norm work (the usual TPU sweet spot —
    # matmuls are the expensive thing to redo, elementwise refills from HBM
    # are nearly free to recompute).
    remat_policy: str = "dots"
    # "pallas" (TPU flash kernel w/ custom-VJP backward; auto-falls back to
    # the XLA path off-TPU), "xla" (einsum softmax), "ring" (sequence-
    # parallel ring attention over the sp axis; requires shard_map context).
    attention_impl: str = "pallas"
    # Mixture-of-experts FFN (0 = dense). Experts shard over the `ep` mesh
    # axis; routing is GShard-style top-k with a per-expert capacity.
    n_experts: int = 0
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    # Token routing implementation. "einsum": GShard dense one-hot
    # dispatch/combine matmuls — SPMD-clean (the expert-dim constrain
    # lowers to the MoE all-to-all when `ep` is in the mesh) and the
    # measured v5e winner despite paying ~e·cap·d uncounted MACs per
    # token each way (BASELINE.md: the MXU burns through the one-hots
    # faster than the memory system serves row-granular indexing).
    # "gather": slot-indexed gathers/scatters moving the same data as
    # bandwidth — measured SLOWER on the chip (32.1% vs 39.3% MFU at
    # moe-125m) and kept as the independent differential-testing oracle
    # for the routing algebra (tests/test_workload_tier.py TestMoE);
    # indices must stay shard-local, so meshes with a resolved expert
    # axis (`ep`, or `fsdp` carrying the expert dim) fall back to einsum.
    moe_impl: str = "einsum"
    # GShard grouped dispatch: tokens route in independent groups of this
    # many sequence positions (0 = one group spanning the sequence).
    # The dispatch/combine one-hot einsums cost b·s·e·cap·d MACs with
    # cap ∝ s/e — QUADRATIC in tokens-per-group, and at moe-125m
    # (s=2048, e=8, cap=640) they outweigh the expert FFN itself: the
    # uncounted routing tax behind the 0.39 MFU. Grouping divides that
    # cost (and the [b,s,e,cap] mask footprint) by the group count while
    # keeping the same static-shaped algebra; capacity is enforced
    # per group (more local drops — standard GShard group_size
    # semantics, arXiv:2006.16668 §3.2).
    moe_group_size: int = 0
    # Microbatches per pipeline round when the mesh has a pp axis
    # (0 = one per stage). More microbatches shrink the GPipe bubble
    # ((pp-1)/(M+pp-1)) at the cost of smaller per-stage matmuls.
    pp_microbatches: int = 0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_token(self, seq: Optional[int] = None) -> float:
        """Approximate training FLOPs per token (fwd+bwd ≈ 6 * active params
        + attention term), for MFU accounting. Single source of truth — the
        bench harness must use this, not its own formula."""
        p = self.active_param_count()
        attn = 12 * self.n_layers * self.dim * (seq or self.max_seq_len)
        return 6 * p + attn

    def _per_layer_params(self, n_ffn_experts: int) -> float:
        d, f = self.dim, self.ffn_dim
        return (
            d * d  # wq
            + 2 * d * (self.n_kv_heads * self.head_dim)  # wk, wv
            + d * d  # wo
            + 3 * d * f * max(n_ffn_experts, 1)  # w1, w2, w3 (per expert)
            + (d * self.n_experts if self.n_experts else 0)  # router
            + 2 * d  # norms
        )

    def param_count(self) -> int:
        d, v = self.dim, self.vocab_size
        per_layer = self._per_layer_params(self.n_experts)
        return int(v * d + self.n_layers * per_layer + d + d * v)

    def geometry(self) -> dict:
        """Shape-invisible geometry for checkpoint metadata: the flattened
        [dim, heads*head_dim] kernels are identical across head regroupings
        (16x64 vs 8x128), so an old checkpoint loads cleanly under a new
        grouping and silently computes different attention. Record + validate
        this at restore (train.checkpoint.CheckpointManager(model_meta=...))."""
        return {
            "dim": self.dim,
            "n_layers": self.n_layers,
            "n_heads": self.n_heads,
            "n_kv_heads": self.n_kv_heads,
            "head_dim": self.head_dim,
            "ffn_dim": self.ffn_dim,
            "vocab_size": self.vocab_size,
            "n_experts": self.n_experts,
            "experts_per_token": self.experts_per_token,
        }

    def active_param_count(self) -> int:
        """Params touched per token (MoE: only the top-k experts)."""
        d, v = self.dim, self.vocab_size
        k = self.experts_per_token if self.n_experts else 0
        per_layer = self._per_layer_params(k)
        return int(v * d + self.n_layers * per_layer + d + d * v)


# Canonical configs. 7B matches Llama-2-7B; the smaller ones size the model
# to chips with less HBM (bench runs on one v5e-lite chip).
#
# Sub-1B head geometry is TPU-first: head_dim 128 (fewer, wider heads) so
# attention blocks fill the MXU's 128-lane tiles. Measured on v5e
# (llama-400m, seq 2048, bs 8): 16 heads x 64 = 45.0% MFU; 8 heads x 128 =
# 61.9% — the narrow-head flash kernel wastes half of every lane register
# and half the QK^T contraction. Param count and FLOPs are identical.
CONFIGS = {
    # remat_policy defaults per config are MEASURED on a single v5e (remat
    # sweep, BASELINE.md): saving the rotated q/k ("+rope") bought ~2 MFU
    # points everywhere it fit; "+norms" helped only where HBM headroom
    # remained (1b bs4, moe). 7b keeps plain "dots" — its per-chip
    # activation budget on a v5e-32 FSDP mesh is unmeasured here and the
    # saved-rope tensors scale with seq 4096.
    "llama2-7b": LlamaConfig(),
    "llama-1b": LlamaConfig(dim=2048, n_layers=16, n_heads=16, n_kv_heads=16,
                            ffn_dim=5504, remat_policy="dots+rope+norms"),
    "llama-400m": LlamaConfig(dim=1024, n_layers=24, n_heads=8, n_kv_heads=8,
                              ffn_dim=2816, remat_policy="dots+rope"),
    "llama-125m": LlamaConfig(dim=768, n_layers=12, n_heads=6, n_kv_heads=6, ffn_dim=2048),
    "llama-tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
        max_seq_len=128, remat=False,
    ),
    # MoE variants (Mixtral-style: SwiGLU experts, top-2 routing, GQA).
    "mixtral-8x7b": LlamaConfig(
        n_kv_heads=8, ffn_dim=14336, max_seq_len=4096, rope_theta=1e6,
        n_experts=8, experts_per_token=2,
    ),
    "moe-125m": LlamaConfig(
        dim=768, n_layers=12, n_heads=6, n_kv_heads=6, ffn_dim=2048,
        n_experts=8, experts_per_token=2, remat_policy="dots+rope+norms",
        # 256-token groups: 8x less dispatch/combine work at seq 2048
        # (cap 640 -> 80 per group) — see moe_group_size.
        moe_group_size=256,
    ),
    "moe-tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
        max_seq_len=128, remat=False, n_experts=4, experts_per_token=2,
    ),
}


class RMSNorm(nn.Module):
    eps: float
    param_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param('scale', nn.initializers.ones, (x.shape[-1],), self.param_dtype)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        # Named for optional checkpointing ("+norms" remat variants): under
        # "dots" the normalized stream is recomputed in the backward as the
        # saved projections' input.
        return checkpoint_name(
            (normed * scale.astype(jnp.float32)).astype(x.dtype), "norm_out"
        )


def rope_table(head_dim: int, max_len: int, theta: float):
    """cos/sin tables, fp32."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # [len, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def gather_rope(cfg: "LlamaConfig", positions):
    """Pre-gathered per-position cos/sin, [b, s, 1, d/2] fp32. Computed
    INSIDE each block (not hoisted to the stack as a scan-broadcast input):
    a broadcast input becomes a residual crossing the forward/backward
    while-loop boundary, and the SPMD partitioner picks conflicting
    shardings for it on the two sides — an involuntary full remat per step.
    Recomputing is a few KB of VPU work per layer; the remat was the real
    cost."""
    cos, sin = rope_table(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    return cos[positions][:, :, None, :], sin[positions][:, :, None, :]


def apply_rope(x, cos, sin):
    """x: [b, s, h, d]; rotate pairs (x0,x1) by pre-gathered cos/sin
    ([1, s, 1, d/2] — see gather_rope). On TPU this lowers to a Pallas
    kernel (ops/rope_pallas.py): the jnp split/concat formulation costs
    lane-dim shuffles and HBM round-trips that measured ~30% of the whole
    train step; the kernel rotates blocks in VMEM (same f32 math)."""
    from ..ops.attention import _on_tpu

    if _on_tpu() and cos.shape[0] == 1 and x.shape[1] == cos.shape[1]:
        from ..ops.rope_pallas import rope_pallas

        return rope_pallas(x, cos[0, :, 0, :], sin[0, :, 0, :])
    from ..parallel.sharding import constrain

    # Materialize the per-head broadcast explicitly and pin it to the layout
    # attention actually uses (heads over tp, batch replicated — the tables
    # are position-only). Left implicit, XLA hoists the broadcast multiplier
    # out of the layer loop as a residual whose sharding is then propagated
    # batch-ish on the forward side but head-tp inside the backward while —
    # a conflict SPMD resolves with an involuntary full remat every step.
    b, s, h, hd = x.shape
    cos = constrain(jnp.broadcast_to(cos, (1, s, h, hd // 2)), None, "sp", "tp", None)
    sin = constrain(jnp.broadcast_to(sin, (1, s, h, hd // 2)), None, "sp", "tp", None)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # Per-position rope, recomputed here (see gather_rope docstring).
        positions = jnp.broadcast_to(jnp.arange(x.shape[1]), (1, x.shape[1]))
        rope = gather_rope(cfg, positions)
        dense = partial(
            nn.DenseGeneral,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
        )
        b, s, _ = x.shape
        # Three separate projections, NOT a fused wqkv: measured on v5e, a
        # fused [d,(h+2kv)*hd] matmul + split is ~7% SLOWER end-to-end than
        # separate kernels (the split forces layout copies of every q/k/v
        # tensor; XLA tiles the narrow matmuls fine).
        q = dense(features=(cfg.n_heads, cfg.head_dim), name="wq")(x)
        k = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wk")(x)
        v = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wv")(x)

        cos, sin = rope
        # Named for optional checkpointing ("dots+rope"): under plain
        # "dots" the rotated q/k are recomputed from the saved projections
        # in the backward (one rope kernel replay each).
        q = checkpoint_name(apply_rope(q, cos, sin), "rope_q")
        k = checkpoint_name(apply_rope(k, cos, sin), "rope_k")

        from ..ops import attention as attn_ops

        if cfg.attention_impl == "pallas":
            out = attn_ops.flash_attention(q, k, v, causal=True)
        elif cfg.attention_impl == "ring":
            from ..ops import ring_attention as ring_ops

            out = ring_ops.sharded_ring_attention(q, k, v)
        else:
            out = attn_ops.xla_attention(q, k, v, causal=True)

        from ..parallel.sharding import DATA_AXES, constrain

        # Attention boundary annotations: the kernel output keeps heads on
        # tp (where the wo contraction consumes them) and the projection
        # back to the residual stream lands directly in the canonical
        # batch layout — without the pins the partitioner is free to pick
        # a head-sharded layout for the residual add and bridge the clash
        # with a resharding copy per layer.
        out = constrain(out, DATA_AXES, "sp", "tp", None)
        return constrain(
            dense(features=cfg.dim, axis=(-2, -1), name="wo")(out),
            DATA_AXES, "sp", None,
        )


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = partial(
            nn.Dense,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
        )
        from ..parallel.sharding import DATA_AXES, constrain

        # Separate gate/up, NOT a fused [d, 2f] w13: measured ~2.5% slower
        # fused on v5e (same split-copy cost as the wqkv experiment).
        gate = dense(cfg.ffn_dim, name="w1")(x)
        up = dense(cfg.ffn_dim, name="w3")(x)
        # Named for optional checkpointing (remat_policy "dots+act"): under
        # plain "dots" the silu*up product is recomputed in the backward.
        # The ffn-dim activation is pinned tp-sharded (where w1/w3 produce
        # it and w2 consumes it) so the elementwise silu*up never collects
        # a tp all-gather between the two matmuls.
        act = checkpoint_name(
            constrain(nn.silu(gate) * up, DATA_AXES, "sp", "tp"), "mlp_act"
        )
        return constrain(dense(cfg.dim, name="w2")(act), DATA_AXES, "sp", None)


class MoE(nn.Module):
    """Mixture-of-experts SwiGLU FFN with GShard-style capacity dispatch.

    Routing is dense-algebra (one-hot dispatch/combine einsums) so the whole
    layer is static-shaped matmuls the MXU can tile — no gather/scatter, no
    data-dependent shapes. Expert weights carry a leading [n_experts] dim
    sharded over the `ep` mesh axis; the dispatch einsum reshards tokens from
    batch-over-(…,ep) to expert-over-ep, which XLA lowers to the MoE
    all-to-all on ICI. Tokens beyond an expert's capacity
    (capacity_factor * s * k / e) are dropped (residual passes them through).

    The Switch-style load-balancing aux loss is sown into the "losses"
    collection; the train step adds it to the LM loss (router_aux_weight).
    """

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        from ..parallel.sharding import DATA_AXES, constrain, moe_expert_axes

        cfg = self.config
        b0, s0, d = x.shape
        # Grouped dispatch (see moe_group_size): fold sequence groups into
        # the batch dim so the routing algebra below runs unchanged on
        # [b·g, group, d] with a per-group capacity. Init traces (short
        # probe sequences) fall through g=1; params are shape-independent.
        groups = 1
        if (cfg.moe_group_size and s0 > cfg.moe_group_size
                and s0 % cfg.moe_group_size == 0):
            groups = s0 // cfg.moe_group_size
            x = x.reshape(b0 * groups, cfg.moe_group_size, d)
        b, s, _ = x.shape
        e, k = cfg.n_experts, cfg.experts_per_token
        cap = max(1, int(cfg.capacity_factor * s * k / e))

        xf = x.astype(jnp.float32)
        router_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.02), name="router",
        )(xf)  # [b, s, e]
        probs = jax.nn.softmax(router_logits, axis=-1)
        gate, idx = jax.lax.top_k(probs, k)  # [b, s, k]
        gate = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)

        # Capacity assignment rank-major (all rank-0 choices win slots
        # before any rank-1 choice), one routing rank at a time — never
        # materializing the k-times-larger [b, s, k, e, cap] intermediate.
        # k is a static config constant, so the Python loop unrolls into
        # one XLA graph. Slot arithmetic runs in int32 (a bf16 cumsum is
        # only integer-exact to 256 — s is 2048). Shared by both routing
        # implementations: per (token, rank) the chosen expert's slot
        # index and whether it won one.
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [b, s, k, e]
        taken = jnp.zeros((b, 1, e), jnp.int32)  # slots already claimed
        pos_ranks, keep_ranks = [], []
        for j in range(k):
            oh = onehot[:, :, j, :]  # [b, s, e]
            pos = jnp.cumsum(oh, axis=1) - oh + taken  # slot index per token
            keep = (pos < cap) & (oh > 0)
            pos_ranks.append(pos)
            keep_ranks.append(keep)
            taken = taken + oh.sum(axis=1, keepdims=True)

        from ..parallel.mesh import current_mesh

        mesh = current_mesh()
        # Expert placement mirrors the weight rules (parallel/sharding.py):
        # `ep` when the mesh has one, else `fsdp` when e divides it (each
        # device holds whole experts; dispatch is the all-to-all), else
        # replicated. The gather oracle needs shard-local indices, so any
        # resolved expert axis falls back to einsum.
        expert_ax, expert_batch_axes = moe_expert_axes(mesh, e)
        use_gather = cfg.moe_impl == "gather" and expert_ax is None

        init = nn.initializers.normal(0.02)
        w1 = self.param("experts_w1", init, (e, d, cfg.ffn_dim), cfg.param_dtype)
        w3 = self.param("experts_w3", init, (e, d, cfg.ffn_dim), cfg.param_dtype)
        w2 = self.param("experts_w2", init, (e, cfg.ffn_dim, d), cfg.param_dtype)

        def expert_ffn(expert_in):  # [e, b, c, d] -> [e, b, c, d]
            gate_h = jnp.einsum("ebcd,edf->ebcf", expert_in, w1.astype(cfg.dtype))
            up_h = jnp.einsum("ebcd,edf->ebcf", expert_in, w3.astype(cfg.dtype))
            return jnp.einsum(
                "ebcf,efd->ebcd", nn.silu(gate_h) * up_h, w2.astype(cfg.dtype)
            )

        if use_gather:
            # Slot-indexed routing (see moe_impl docstring: measured
            # slower than the einsums on TPU; kept as the differential
            # oracle for the routing algebra). Flat slot id per (token,
            # rank): the chosen expert's slot, or the overflow bucket
            # e*cap when the token lost the capacity race.
            pos_c = jnp.stack([
                jnp.take_along_axis(p, idx[:, :, j, None], axis=2)[..., 0]
                for j, p in enumerate(pos_ranks)
            ], axis=-1)  # [b, s, k]
            keep_c = jnp.stack([
                jnp.take_along_axis(kp, idx[:, :, j, None], axis=2)[..., 0]
                for j, kp in enumerate(keep_ranks)
            ], axis=-1)  # [b, s, k] bool
            fslot = jnp.where(keep_c, idx * cap + pos_c, e * cap)  # [b, s, k]

            def route_row(xb, fslot_b):
                # xb [s, d]; fslot_b [s, k] -> [e*cap, d] (unfilled rows 0)
                flat = fslot_b.reshape(-1)
                token_of_slot = jnp.zeros((e * cap + 1,), jnp.int32).at[
                    flat].set(jnp.repeat(jnp.arange(s, dtype=jnp.int32), k),
                              mode="drop")
                valid = jnp.zeros((e * cap + 1,), cfg.dtype).at[flat].set(
                    1.0, mode="drop")
                gathered = jnp.take(xb, token_of_slot[:-1], axis=0)
                return gathered * valid[:-1, None]

            expert_in_b = jax.vmap(route_row)(
                x.astype(cfg.dtype), fslot
            )  # [b, e*cap, d]
            expert_in = expert_in_b.reshape(b, e, cap, d).transpose(1, 0, 2, 3)

            out = expert_ffn(expert_in)  # [e, b, c, d]

            # Combine: gather each (token, rank)'s slot output and weight
            # by its gate; the overflow row is zeros so dropped tokens
            # contribute nothing (residual passes them through).
            out_flat = jnp.concatenate([
                out.transpose(1, 0, 2, 3).reshape(b, e * cap, d),
                jnp.zeros((b, 1, d), out.dtype),
            ], axis=1)  # [b, e*cap+1, d]

            def combine_row(out_b, fslot_b, gate_b):
                contrib = jnp.take(out_b, fslot_b.reshape(-1), axis=0)
                contrib = contrib.reshape(s, k, d).astype(jnp.float32)
                return (contrib * gate_b[..., None]).sum(axis=1)

            y = jax.vmap(combine_row)(out_flat, fslot, gate)
        else:
            # GShard dense-algebra routing: every [b, s, e, cap]-shaped
            # tensor is built directly in model dtype (at moe-125m these
            # are ~170 MB EACH in fp32), and the dispatch mask is derived
            # from combine (> 0) rather than accumulated as a second
            # chain — halving the construction traffic; a gate
            # underflowing to 0 in bf16 just drops that token to the
            # residual path.
            combine = jnp.zeros((b, s, e, cap), cfg.dtype)
            for j in range(k):
                keep = keep_ranks[j].astype(cfg.dtype)
                slot = jax.nn.one_hot(jnp.minimum(pos_ranks[j], cap - 1), cap,
                                      dtype=cfg.dtype)  # [b, s, e, cap]
                combine = combine + (
                    keep * gate[:, :, j, None].astype(cfg.dtype)
                )[..., None] * slot
            # Token-layout routing masks pinned to the canonical batch
            # layout: left unconstrained, the partitioner propagates the
            # expert-sharded dispatch OUTPUT's layout backwards into the
            # mask construction and the whole residual stream reshards
            # around the MoE layer every step.
            combine = constrain(combine, DATA_AXES, "sp", None, None)
            dispatch = (combine > 0).astype(cfg.dtype)

            # Dispatch: tokens -> per-expert slots. The constraint reshards
            # the expert dim onto the resolved expert axis (the MoE
            # all-to-all); batch stays on the remaining data axes.
            # dispatch is a 0/1 mask (exactly representable in bf16), so
            # the largest routing contraction runs at full MXU rate in
            # model dtype.
            expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x.astype(cfg.dtype))
            expert_in = constrain(
                expert_in, expert_ax, expert_batch_axes, None, None
            )
            out = expert_ffn(expert_in)
            out = constrain(out, expert_ax, expert_batch_axes, None, None)

            # Combine: weighted return all-to-all back to token layout.
            # bf16 operands / fp32 accumulation: a genuinely fp32 einsum
            # here runs the MXU at a fraction of its bf16 rate. The gate
            # weights are O(1) softmax probs — a bf16 combine loses ~0.4%
            # relative on them, standard for MoE training; the router
            # itself stays fp32 above.
            y = jnp.einsum(
                "bsec,ebcd->bsd", combine, out,
                preferred_element_type=jnp.float32,
            )

        # Switch load-balance loss: e * Σ_i f_i·P_i (f = dispatch fraction,
        # P = mean router prob); minimized at uniform routing. Means over
        # (batch, position) are group-invariant: the grouped reshape
        # changes which tokens race for capacity, not these statistics.
        f_frac = onehot.astype(jnp.float32).sum(axis=2).mean(axis=(0, 1)) / k
        p_mean = probs.mean(axis=(0, 1))
        aux = e * jnp.sum(f_frac * p_mean) * cfg.router_aux_weight
        self.sow("losses", "moe_aux", aux)

        y = y.astype(x.dtype)
        if groups > 1:
            y = y.reshape(b0, s0, d)
        return constrain(y, DATA_AXES, "sp", None)


class Block(nn.Module):
    """One decoder layer. Signature is scan-compatible: carries `x` only
    (rope is recomputed inside Attention — see gather_rope)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        from ..parallel.sharding import DATA_AXES, constrain

        cfg = self.config
        # Pin activations to the canonical layout at every residual-stream
        # boundary — block entry, between the attention and MLP sublayers,
        # block exit — so the partitioner doesn't oscillate between layouts
        # across the residual stream (a no-op without a scoped mesh).
        x = constrain(x, DATA_AXES, "sp", None)
        x = x + Attention(cfg, name="attention")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="attention_norm")(x)
        )
        x = constrain(x, DATA_AXES, "sp", None)
        ffn = MoE(cfg, name="feed_forward") if cfg.n_experts else MLP(cfg, name="feed_forward")
        x = x + ffn(RMSNorm(cfg.norm_eps, cfg.param_dtype, name="ffn_norm")(x))
        return constrain(x, DATA_AXES, "sp", None), None


# Saveable-tensor vocabulary for the "dots+..." remat policies: token ->
# checkpoint_name tags. The policy string is an open composition ("dots"
# plus any "+"-joined subset, order-free) so bench sweeps can tune the
# HBM-vs-recompute point per config without a code change
# (TF_OPERATOR_REMAT_POLICY in bench.py).
REMAT_SAVEABLE = {
    "act": ("mlp_act",),
    "rope": ("rope_q", "rope_k"),
    "norms": ("norm_out",),
}


def _remat_policy(cfg: LlamaConfig):
    """Checkpoint policy under remat. "dots" additionally saves the
    flash-attention outputs (tagged flash_o/flash_lse in
    ops/flash_pallas.py): with q/k/v already dot-saveable, every VJP
    residual is checkpointed and the backward replay skips re-running the
    forward kernel. The "dots+..." variants trade more HBM for less
    backward recompute (remat sweep, BASELINE.md): any "+"-joined
    combination of REMAT_SAVEABLE tokens, e.g. "dots+rope+norms"."""
    name = cfg.remat_policy
    if name == "nothing":
        return jax.checkpoint_policies.nothing_saveable
    parts = name.split("+")
    if parts[0] != "dots" or not all(p in REMAT_SAVEABLE for p in parts[1:]):
        raise ValueError(
            f"unknown remat_policy {name!r}: expected 'nothing' or 'dots' "
            f"joined with any of {sorted(REMAT_SAVEABLE)} (e.g. 'dots+rope')"
        )
    names = [tag for p in parts[1:] for tag in REMAT_SAVEABLE[p]]
    return jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        jax.checkpoint_policies.save_only_these_names(
            "flash_o", "flash_lse", *names
        ),
    )


class Llama(nn.Module):
    """Decoder stack. Layers run under `nn.scan` over stacked parameters
    (leading [n_layers] dim) with `nn.remat` on the body: one compiled block
    regardless of depth (constant compile time) and guaranteed per-layer
    rematerialization — only block-boundary activations survive the forward
    pass, the backward recomputes inside one layer at a time. This is the
    canonical XLA/TPU pattern for deep transformer training."""

    # Capability flag for train_step.loss_fn: __call__(return_hidden=True)
    # yields pre-logits hidden states for the memory-chunked CE path.
    supports_return_hidden = True

    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        cfg = self.config
        b, s = tokens.shape
        from ..parallel.sharding import DATA_AXES, constrain

        x = nn.Embed(
            cfg.vocab_size,
            cfg.dim,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.initializers.normal(0.02),
            name="tok_embeddings",
        )(tokens)
        # Land the lookup output directly in the canonical activation layout
        # (batch over data axes) instead of letting the vocab-sharded gather
        # output's layout propagate into the first block.
        x = constrain(x, DATA_AXES, "sp", None)

        from ..parallel.mesh import current_mesh

        mesh = current_mesh()
        pp = int(mesh.shape.get("pp", 1)) if mesh is not None else 1
        if pp > 1 and not self.is_initializing():
            # Pipeline-parallel apply: the scanned params (created by the
            # init path below, stacked [n_layers, ...]) are split into pp
            # contiguous stages and driven through the GPipe schedule
            # (parallel/pipeline.py). Param STRUCTURE is identical to the
            # scan path, so checkpoints are interchangeable.
            if cfg.n_experts:
                raise NotImplementedError(
                    "MoE + pipeline parallelism is not supported yet "
                    "(the blocks' sown aux losses don't cross the pipeline)"
                )
            if cfg.attention_impl == "ring" and "sp" in mesh.shape:
                raise NotImplementedError(
                    "ring attention + pipeline parallelism is not supported "
                    "yet (a nested full-mesh shard_map is illegal inside "
                    "the pp-manual region)"
                )
            from ..parallel.pipeline import pipeline_apply, split_stages

            layer_params = self.scope.get_variable("params", "layers")
            # parent=None: a detached (pure) Block — created inside this
            # compact __call__, it would otherwise auto-register as a child
            # module and its .apply would corrupt the trace.
            blk = Block(cfg, parent=None)

            def apply_one(p, carry):
                y, _ = blk.apply({"params": p}, carry)
                return y

            if cfg.remat:
                apply_one = jax.checkpoint(
                    apply_one, prevent_cse=False, policy=_remat_policy(cfg)
                )

            def stage_fn(p_stage, xm):
                def body(carry, p):
                    return apply_one(p, carry), None

                y, _ = jax.lax.scan(body, xm, p_stage)
                return y

            x = pipeline_apply(
                stage_fn,
                split_stages(layer_params, pp),
                x,
                num_microbatches=cfg.pp_microbatches or pp,
                mesh=mesh,
            )
        else:
            block = Block
            if cfg.remat:
                block = nn.remat(
                    Block, prevent_cse=False, policy=_remat_policy(cfg)
                )
            scanned = nn.scan(
                block,
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )
            x, _ = scanned(cfg, name="layers")(x)

        x = RMSNorm(cfg.norm_eps, cfg.param_dtype, name="norm")(x)
        if return_hidden:
            # Pre-logits hidden for memory-chunked losses: the train step
            # applies the "output" head per sequence chunk (lax.map) so the
            # [b, s, vocab] fp32 logits tensor never exists whole in HBM.
            # (Init always runs the default path, so head params exist.)
            return x
        logits = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name="output",
        )(x)
        return logits.astype(jnp.float32)


def make_model(name_or_config) -> Llama:
    if isinstance(name_or_config, str):
        name_or_config = CONFIGS[name_or_config]
    return Llama(name_or_config)


def init_params(model: Llama, rng, batch: int = 1, seq: Optional[int] = None):
    seq = seq or min(model.config.max_seq_len, 128)
    tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
    variables = model.init(rng, tokens)
    # MoE layers sow a "losses" collection during init; only "params" are
    # trainable state (anything else here would reach the optimizer).
    return {"params": variables["params"]}
