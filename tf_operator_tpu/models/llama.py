"""Llama-family decoder-only transformer (Flax linen), TPU-first.

The flagship model for the JAXJob examples and the benchmark harness
(BASELINE.md: Llama-2-7B FSDP on v5e-32). Design targets the MXU/HBM:

- bf16 params and activations; fp32 only where numerics demand it
  (RMSNorm accumulation, rotary tables, softmax, final logits).
- All FLOPs in large batched matmuls (einsum) that XLA tiles onto the MXU.
- `remat` on each block trades FLOPs for HBM (checkpointing).
- No data-dependent Python control flow — one static graph under jit.
- Attention defaults to `tf_operator_tpu.ops.attention`, which lowers to a
  Pallas flash-attention kernel on TPU and falls back to a fused XLA path
  elsewhere.

Reference note: the reference repo contains no model code (it is a control
plane; workloads live in user containers). Architecture follows the public
Llama-2 description (RMSNorm, RoPE, SwiGLU, optional GQA).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    remat: bool = True
    # "pallas" (TPU flash kernel w/ custom-VJP backward; auto-falls back to
    # the XLA path off-TPU), "xla" (einsum softmax), "ring" (sequence-
    # parallel ring attention over the sp axis; requires shard_map context).
    attention_impl: str = "pallas"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def flops_per_token(self, seq: Optional[int] = None) -> float:
        """Approximate training FLOPs per token (fwd+bwd ≈ 6 * params +
        attention term), for MFU accounting. Single source of truth — the
        bench harness must use this, not its own formula."""
        p = self.param_count()
        attn = 12 * self.n_layers * self.dim * (seq or self.max_seq_len)
        return 6 * p + attn

    def param_count(self) -> int:
        d, v, f = self.dim, self.vocab_size, self.ffn_dim
        per_layer = (
            d * d  # wq
            + 2 * d * (self.n_kv_heads * self.head_dim)  # wk, wv
            + d * d  # wo
            + 3 * d * f / 1  # w1, w2, w3 (w2 transposed but same count)
            + 2 * d  # norms
        )
        return int(v * d + self.n_layers * per_layer + d + d * v)


# Canonical configs. 7B matches Llama-2-7B; the smaller ones size the model
# to chips with less HBM (bench runs on one v5e-lite chip).
CONFIGS = {
    "llama2-7b": LlamaConfig(),
    "llama-1b": LlamaConfig(dim=2048, n_layers=16, n_heads=16, n_kv_heads=16, ffn_dim=5504),
    "llama-400m": LlamaConfig(dim=1024, n_layers=24, n_heads=16, n_kv_heads=16, ffn_dim=2816),
    "llama-125m": LlamaConfig(dim=768, n_layers=12, n_heads=12, n_kv_heads=12, ffn_dim=2048),
    "llama-tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_dim=128,
        max_seq_len=128, remat=False,
    ),
}


class RMSNorm(nn.Module):
    eps: float
    param_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param('scale', nn.initializers.ones, (x.shape[-1],), self.param_dtype)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale.astype(jnp.float32)).astype(x.dtype)


def rope_table(head_dim: int, max_len: int, theta: float):
    """cos/sin tables, fp32."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # [len, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin, positions):
    """x: [b, s, h, d]; rotate pairs (x0,x1) by position-dependent angles."""
    cos = cos[positions][:, :, None, :]  # [b, s, 1, d/2]
    sin = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        dense = partial(
            nn.DenseGeneral,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
        )
        b, s, _ = x.shape
        q = dense(features=(cfg.n_heads, cfg.head_dim), name="wq")(x)
        k = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wk")(x)
        v = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wv")(x)

        cos, sin = rope_table(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        from ..ops import attention as attn_ops

        if cfg.attention_impl == "pallas":
            out = attn_ops.flash_attention(q, k, v, causal=True)
        elif cfg.attention_impl == "ring":
            from ..ops import ring_attention as ring_ops

            out = ring_ops.ring_attention(q, k, v, axis_name="sp")
        else:
            out = attn_ops.xla_attention(q, k, v, causal=True)

        return dense(features=cfg.dim, axis=(-2, -1), name="wo")(out)


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = partial(
            nn.Dense,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
        )
        gate = dense(cfg.ffn_dim, name="w1")(x)
        up = dense(cfg.ffn_dim, name="w3")(x)
        return dense(cfg.dim, name="w2")(nn.silu(gate) * up)


class Block(nn.Module):
    """One decoder layer. Signature is scan-compatible: carries `x`, passes
    `positions` through as a second carry-free broadcast input."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        x = x + Attention(cfg, name="attention")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="attention_norm")(x), positions
        )
        x = x + MLP(cfg, name="feed_forward")(
            RMSNorm(cfg.norm_eps, cfg.param_dtype, name="ffn_norm")(x)
        )
        return x, None


class Llama(nn.Module):
    """Decoder stack. Layers run under `nn.scan` over stacked parameters
    (leading [n_layers] dim) with `nn.remat` on the body: one compiled block
    regardless of depth (constant compile time) and guaranteed per-layer
    rematerialization — only block-boundary activations survive the forward
    pass, the backward recomputes inside one layer at a time. This is the
    canonical XLA/TPU pattern for deep transformer training."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.config
        b, s = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        x = nn.Embed(
            cfg.vocab_size,
            cfg.dim,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.initializers.normal(0.02),
            name="tok_embeddings",
        )(tokens)

        block = Block
        if cfg.remat:
            block = nn.remat(
                Block,
                prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable,
            )
        scanned = nn.scan(
            block,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,  # positions: same every layer
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = scanned(cfg, name="layers")(x, positions)

        x = RMSNorm(cfg.norm_eps, cfg.param_dtype, name="norm")(x)
        logits = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02),
            name="output",
        )(x)
        return logits.astype(jnp.float32)


def make_model(name_or_config) -> Llama:
    if isinstance(name_or_config, str):
        name_or_config = CONFIGS[name_or_config]
    return Llama(name_or_config)


def init_params(model: Llama, rng, batch: int = 1, seq: Optional[int] = None):
    seq = seq or min(model.config.max_seq_len, 128)
    tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
    return model.init(rng, tokens)
